"""Paged KV cache: device-resident pages + host-side page allocator.

Replaces the reference's LRU-dict KVCacheManager that generation never reads
(reference serve/server.py:57-87, defect SURVEY §2.4.2). Design is
vLLM-style paging mapped onto XLA's static-shape world:

- All layers' pages live in two arrays [L, num_pages, Nkv, page_size, D] in
  HBM (one allocation, no fragmentation).
- Page 0 is reserved scratch: every unused block-table entry points at it,
  so the jitted decode step can run over ALL slots every step — inactive
  slots write into scratch and read garbage that their length mask hides.
- Allocation/free is host-side (cheap integer bookkeeping between device
  steps); the device only ever sees the dense block_tables array.
"""

from __future__ import annotations

import hashlib
import logging
import math
from collections import OrderedDict
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..config.schema import ModelConfig
from ..analysis.annotations import engine_thread_only

logger = logging.getLogger("llmctl.serve.kv_cache")


def prefix_page_hashes(tokens, page_size: int) -> list[bytes]:
    """Chain hashes for every FULL page of a token prefix.

    ``h_i`` digests tokens[0 : (i+1)*page_size] (via the chain), because a
    page's K/V content depends on the *entire* prefix through attention —
    two prompts may share page i only if they agree on every token through
    its end. Only full pages are shareable: a partially-filled page keeps
    receiving decode writes and stays private to its slot.
    """
    arr = np.asarray(tokens, np.int32)
    out, h = [], b""
    for i in range(len(arr) // page_size):
        h = hashlib.blake2b(
            h + arr[i * page_size:(i + 1) * page_size].tobytes(),
            digest_size=16).digest()
        out.append(h)
    return out


def slice_page_payload(content: dict, n: int) -> dict:
    """First ``n`` pages of an ``extract_pages``-schema payload (plain
    arrays or quantized {values, scale} dicts; page axis is 1)."""
    total = int(content["num_pages"])
    if not 0 < n <= total:
        raise ValueError(
            f"slice_page_payload: want {n} of {total} page(s)")

    def cut(node):
        if isinstance(node, dict):
            return {k: cut(v) for k, v in node.items()}
        return np.asarray(node)[:, :n]
    return {"k": cut(content["k"]), "v": cut(content["v"]),
            "num_pages": n}


def concat_page_payloads(a: dict, b: dict) -> dict:
    """Concatenate two page payloads along the page axis — the
    salvage-tail splice (serve/engine.py ``_maybe_fetch_salvage_tail``):
    a crash-salvaged partial payload grows by the chain pages a sibling
    replica's cache still held. Quantized and plain payloads must not
    mix (the write path validates shapes again before any scatter)."""

    def cat(x, y):
        if isinstance(x, dict) != isinstance(y, dict):
            raise ValueError(
                "concat_page_payloads: quantized/plain payload mismatch")
        if isinstance(x, dict):
            if set(x) != set(y):
                raise ValueError(
                    f"concat_page_payloads: quantized parts differ "
                    f"({sorted(x)} vs {sorted(y)})")
            return {k: cat(x[k], y[k]) for k in x}
        return np.concatenate([np.asarray(x), np.asarray(y)], axis=1)
    return {"k": cat(a["k"], b["k"]), "v": cat(a["v"], b["v"]),
            "num_pages": int(a["num_pages"]) + int(b["num_pages"])}


class PagedKVCache:
    def __init__(
        self,
        cfg: ModelConfig,
        num_slots: int,
        max_seq_len: int,
        page_size: int = 16,
        num_pages: int = 0,
        hbm_budget_gb: float = 4.0,
        dtype=jnp.bfloat16,
        page_sharding=None,     # NamedSharding over the kv-head axis for
                                # tensor-parallel serving (None = one device)
        quantized=False,        # False|"none" | True|"int8" | "int4"
    ):
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len
        self.page_size = page_size
        self.max_pages_per_slot = math.ceil(max_seq_len / page_size)
        # normalize the quantization kind: legacy bool callers mean int8
        if quantized is True:
            kind = "int8"
        elif not quantized or quantized == "none":
            kind = "none"
        else:
            kind = str(quantized)
        if kind not in ("none", "int8", "int4"):
            raise ValueError(f"unknown KV quantization {quantized!r} "
                             "(none|int8|int4)")
        if kind == "int4" and page_size % 2:
            raise ValueError(
                f"int4 KV pages pack two page slots per byte; page_size "
                f"{page_size} must be even")
        self.quant_kind = kind
        self.quantized = kind != "none"
        if num_pages <= 0:
            if kind == "int4":
                # packed nibbles (D/2 bytes) + fp32 per-(token, kv-head)
                # scale, K and V — the 2x-over-int8 capacity claim
                bytes_per_page = (2 * cfg.num_layers * page_size
                                  * cfg.num_kv_heads
                                  * (cfg.head_dim // 2 + 4))
            elif kind == "int8":
                # int8 values + fp32 per-(token, kv-head) scale, K and V
                bytes_per_page = (2 * cfg.num_layers * page_size
                                  * cfg.num_kv_heads * (cfg.head_dim + 4))
            else:
                bytes_per_page = (2 * cfg.num_layers * page_size
                                  * cfg.num_kv_heads * cfg.head_dim
                                  * jnp.dtype(dtype).itemsize)
            num_pages = max(int(hbm_budget_gb * 1e9 // bytes_per_page), 2)
        # never more than every slot fully resident (+1 scratch)
        num_pages = min(num_pages, num_slots * self.max_pages_per_slot + 1)
        self.num_pages = num_pages
        self.dtype = dtype

        # [L, NP, Nkv, PS, D] — (PS, D) minor-most so the Pallas decode
        # kernel can DMA one [PS, D] page tile per (kv-head, page) grid step
        # (TPU block shapes must end in the tiled dims)
        shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, page_size,
                 cfg.head_dim)
        self.page_sharding = page_sharding
        self.k_pages = self._new_pages(shape, dtype)
        self.v_pages = self._new_pages(shape, dtype)

        # host-side state; page 0 is scratch and never allocated
        self._free: list[int] = list(range(1, num_pages))
        self._owned: dict[int, list[int]] = {}            # slot -> pages
        self._chain_len: dict[int, int] = {}   # slot -> table entries used
        self.block_tables = np.zeros((num_slots, self.max_pages_per_slot),
                                     np.int32)

        # prefix cache: refcounted shared pages + LRU of evictable ones.
        # A page is in exactly one of: _free, referenced (_ref > 0), or
        # _evictable (ref == 0 but content cached for future hits).
        self._ref = np.zeros(num_pages, np.int32)
        self._hash_to_page: dict[bytes, int] = {}
        self._page_to_hash: dict[int, bytes] = {}
        self._evictable: OrderedDict[int, None] = OrderedDict()
        self.prefix_hits = 0          # pages served from cache
        self.prefix_queries = 0       # full pages looked up
        # tiered fleet KV store (serve/fleet/kv_store.py): when set,
        # called with (hashes, multi-page extract payload) covering the
        # cached pages an allocation evicted — the demotion seam.
        # Evictions are BATCHED per allocation call: _take_free_page
        # only records (hash, page) pairs and the allocation flushes
        # them through ONE device gather before returning (the pages'
        # content is untouched until a later dispatch writes them, and
        # every write happens on this same engine thread). A hook
        # failure must never break allocation, so the flush is guarded.
        # None (the default) changes nothing.
        self.demote_hook = None
        self._demote_pending: list[tuple[bytes, int]] = []

    def _new_pages(self, shape, dtype):
        """Allocate a (possibly int8/int4-quantized, possibly tensor-
        parallel-sharded) page buffer. ``shape`` is always the LOGICAL
        [L, NP, Nkv, PS, D] geometry; the int4 buffer packs the page-slot
        axis to PS/2 bytes internally (Int4Pages.shape reports logical)."""
        import jax
        if self.quantized:
            from ..ops.paged_attention import Int4Pages, QuantPages
            if self.quant_kind == "int4":
                # two page slots per byte along the slot axis; the scale
                # keeps the full per-slot [L, NP, Nkv, PS] tile
                buf = Int4Pages(
                    jnp.zeros((*shape[:-2], shape[-2] // 2, shape[-1]),
                              jnp.uint8),
                    jnp.zeros(shape[:-1], jnp.float32))
            else:
                # scale layout is the kernel-friendly per-page tensor
                # [L, NP, Nkv, PS] (no trailing singleton — QuantPages doc)
                buf = QuantPages(jnp.zeros(shape, jnp.int8),
                                 jnp.zeros(shape[:-1], jnp.float32))
            if self.page_sharding is not None:
                from jax.sharding import NamedSharding, PartitionSpec
                # rank-aware: the VALUES leaf keeps the full 5-entry spec
                # (int4 packing shrinks the slot axis but not the rank —
                # the kv-head shard axis is untouched); the scale leaf is
                # one rank lower, so trim the head-dim entry off the spec
                ps = self.page_sharding
                scale_sharding = NamedSharding(
                    ps.mesh, PartitionSpec(*tuple(ps.spec)[:len(shape) - 1]))
                return type(buf)(
                    jax.device_put(buf.values, ps),
                    jax.device_put(buf.scale, scale_sharding))
            return buf
        buf = jnp.zeros(shape, dtype)
        if self.page_sharding is not None:
            return jax.device_put(buf, self.page_sharding)
        return buf

    # -- accounting ----------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free) + len(self._evictable)

    def pages_needed(self, num_tokens: int) -> int:
        return math.ceil(max(num_tokens, 1) / self.page_size)

    def can_allocate(self, num_tokens: int) -> bool:
        return self.pages_needed(num_tokens) <= self.free_pages

    def can_ever_allocate(self, num_tokens: int) -> bool:
        """Whether an EMPTY cache could hold this many tokens (page 0 is
        reserved scratch)."""
        return self.pages_needed(num_tokens) <= self.num_pages - 1

    def hbm_bytes(self) -> int:
        def one(buf):
            from ..ops.paged_attention import QuantPages
            if isinstance(buf, QuantPages):
                return buf.values.size + buf.scale.size * 4
            return int(np.prod(buf.shape)) * jnp.dtype(self.dtype).itemsize
        return one(self.k_pages) + one(self.v_pages)

    # -- alloc / grow / free -------------------------------------------------

    def _take_free_page(self) -> int:
        """Pop a free page, evicting the LRU cached page if needed. An
        evicted hashed page is queued for the demote hook (tiered fleet
        KV store); the allocation that triggered the eviction flushes
        the queue in one batched extract before returning — HBM
        eviction then moves pages down a tier instead of destroying
        them, at one device gather per allocation instead of one per
        page."""
        if self._free:
            return self._free.pop()
        if self._evictable:
            page, _ = self._evictable.popitem(last=False)   # oldest first
            h = self._page_to_hash.pop(page, None)
            if h is not None:
                self._hash_to_page.pop(h, None)
                if self.demote_hook is not None:
                    self._demote_pending.append((h, page))
            return page
        raise RuntimeError("KV cache OOM: no free or evictable pages")

    def _flush_demotions(self) -> None:
        """Hand every eviction queued by ``_take_free_page`` to the
        demote hook in one batched extract. Must run before the caller
        releases the engine lock (the evicted pages' content is only
        guaranteed until the next dispatch writes them)."""
        if not self._demote_pending:
            return
        pairs, self._demote_pending = self._demote_pending, []
        hook = self.demote_hook
        if hook is None:
            return
        try:
            content = self._extract_pages_idx(
                np.asarray([p for _h, p in pairs], np.int32))
            hook([h for h, _p in pairs], content)
        except Exception:
            logger.exception(
                "KV page demotion hook failed; %d page(s) evicted "
                "without demoting", len(pairs))

    def _drop_ref(self, page: int) -> None:
        self._ref[page] -= 1
        if self._ref[page] <= 0:
            self._ref[page] = 0
            if page in self._page_to_hash:
                self._evictable[page] = None    # keep content, reclaimable
            else:
                self._free.append(page)

    def allocate(self, slot: int, num_tokens: int,
                 prefix_pages: Optional[list[int]] = None) -> None:
        """Give ``slot`` enough pages for ``num_tokens`` tokens.

        ``prefix_pages`` (already pinned via ``pin_pages``) become the head
        of the slot's block table; only the remainder is freshly allocated.
        """
        prefix_pages = prefix_pages or []
        need = self.pages_needed(num_tokens)
        fresh = need - len(prefix_pages)
        if fresh > self.free_pages:
            raise RuntimeError(
                f"KV cache OOM: need {fresh} pages, {self.free_pages} free")
        pages = [self._take_free_page() for _ in range(fresh)]
        for p in pages:
            self._ref[p] = 1
        # slot owns refs on fresh pages only; prefix pins are tracked by
        # the engine per request and dropped via unpin_pages on release
        self._owned[slot] = pages
        table = list(prefix_pages) + pages
        self.block_tables[slot, :] = 0
        self.block_tables[slot, :len(table)] = table
        self._chain_len[slot] = len(table)
        self._flush_demotions()

    def slot_capacity_tokens(self, slot: int) -> int:
        """Tokens the slot's current page chain can hold."""
        return self._chain_len.get(slot, 0) * self.page_size

    def extend_slot(self, slot: int, num_tokens: int) -> bool:
        """Grow ``slot``'s chain to cover ``num_tokens`` (on-demand
        admission). Returns False — allocating nothing — if the pool can't
        supply every page needed; the engine then preempts a victim and
        retries. All-or-nothing keeps the failure path trivial: no partial
        growth to unwind."""
        need = self.pages_needed(num_tokens) - self._chain_len.get(slot, 0)
        if need <= 0:
            return True
        if need > self.free_pages:
            return False
        start = self._chain_len.get(slot, 0)
        pages = [self._take_free_page() for _ in range(need)]
        for p in pages:
            self._ref[p] = 1
        self._owned.setdefault(slot, []).extend(pages)
        self.block_tables[slot, start:start + need] = pages
        self._chain_len[slot] = start + need
        self._flush_demotions()
        return True

    def release(self, slot: int) -> None:
        for page in self._owned.pop(slot, []):
            self._drop_ref(page)
        self.block_tables[slot, :] = 0
        self._chain_len.pop(slot, None)

    # -- swap (preemption to host memory) ------------------------------------

    @engine_thread_only
    def extract_slot(self, slot: int) -> dict:
        """Copy ``slot``'s written pages to HOST memory (swap-out half of
        preemption=swap). One device fetch per buffer — the page gather
        runs on-device, only the slot's own pages cross the link."""
        return self.extract_slot_pages(slot, 0, self._chain_len.get(slot, 0))

    @engine_thread_only
    def extract_slot_pages(self, slot: int, lo: int, hi: int) -> dict:
        """Copy chain entries [lo, hi) of ``slot`` to host memory.

        The page-range form is the two-phase migration courier
        (serve/fleet/migration.py): phase 1 pre-copies the full (immutable)
        pages while decode keeps appending to the tail, phase 2
        stop-and-copies only [full, written) — the partial tail plus pages
        filled since the pre-copy. Payloads are plain numpy (host) arrays,
        so they survive the source engine's death and serialize for the
        cross-host courier (serve/fleet/transport.py).

        Bounds are validated up front: an out-of-range request would
        otherwise silently gather scratch page 0 (zeros presented as real
        KV — wrong tokens downstream, no error)."""
        chain = self._chain_len.get(slot, 0)
        if not 0 <= lo <= hi <= chain:
            raise ValueError(
                f"extract_slot_pages: range [{lo}, {hi}) outside slot "
                f"{slot}'s chain of {chain} page(s)")
        return self._extract_pages_idx(self.block_tables[slot, lo:hi].copy())

    @engine_thread_only
    def extract_pages(self, pages: list[int]) -> dict:
        """Copy arbitrary page ids to host memory — the owner half of the
        fleet-global prefix fetch (serve/fleet/): the pages come from
        ``lookup_prefix``, not any slot's chain. Same payload schema as
        :meth:`extract_slot_pages`. Page ids are bounds-checked (scratch
        page 0 is never a cache page; an out-of-range id would gather
        garbage presented as real KV)."""
        bad = [int(p) for p in pages if not 0 < int(p) < self.num_pages]
        if bad:
            raise ValueError(
                f"extract_pages: page id(s) {bad} outside (0, "
                f"{self.num_pages})")
        return self._extract_pages_idx(np.asarray(pages, np.int32))

    def _extract_pages_idx(self, pages: np.ndarray) -> dict:
        idx = jnp.asarray(pages)

        def grab(buf):
            from ..ops.paged_attention import QuantPages
            if isinstance(buf, QuantPages):
                return {"values": np.asarray(buf.values[:, idx]),
                        "scale": np.asarray(buf.scale[:, idx])}
            return np.asarray(buf[:, idx])
        return {"k": grab(self.k_pages), "v": grab(self.v_pages),
                "num_pages": int(len(pages))}

    def _restore_fn(self, n_bucket: int):
        """Jitted donated page-write for swap-in: out-of-place .at[].set
        outside jit would copy the WHOLE pool per restore (transient 2x
        HBM + O(pool) traffic); under jit with donation XLA scatters in
        place. One program per power-of-two page-count bucket; short
        restores pad with scratch page 0 (writing zeros there is the
        cache's documented no-op)."""
        import jax
        if not hasattr(self, "_restore_cache"):
            self._restore_cache = {}
        if n_bucket not in self._restore_cache:
            def write(k_pages, v_pages, idx, kd, vd):
                from ..ops.paged_attention import QuantPages

                def put(buf, data):
                    if isinstance(buf, QuantPages):
                        # type(buf): Int4Pages payloads (packed uint8
                        # values) restore through the same scatter
                        return type(buf)(
                            buf.values.at[:, idx].set(data["values"]),
                            buf.scale.at[:, idx].set(data["scale"]))
                    return buf.at[:, idx].set(data.astype(buf.dtype))
                return put(k_pages, kd), put(v_pages, vd)
            self._restore_cache[n_bucket] = jax.jit(
                write, donate_argnums=(0, 1))
        return self._restore_cache[n_bucket]

    @engine_thread_only
    def restore_slot(self, slot: int, content: dict) -> bool:
        """Swap-in: allocate fresh pages for the slot and write the saved
        K/V back. Returns False (allocating nothing) when the pool can't
        supply the pages — the caller falls back to recompute."""
        if not isinstance(content, dict) or "num_pages" not in content:
            raise ValueError(
                "restore payload must be a dict with 'num_pages'; got "
                f"{type(content).__name__}")
        n = int(content["num_pages"])
        if n > self.free_pages:
            return False
        self.allocate(slot, n * self.page_size)
        self.write_slot_pages(slot, content)
        return True

    def _validate_payload(self, slot: int, content: dict, lo: int) -> int:
        """Schema + bounds check for a restore payload; returns its page
        count. Raises ValueError naming exactly what is malformed.
        Bounds before shapes, so a wrong page COUNT names the slot's
        chain rather than a derived shape mismatch."""
        n = self._parse_num_pages(content)
        chain = self._chain_len.get(slot, 0)
        if lo < 0 or lo + n > chain:
            raise ValueError(
                f"restore payload covers chain entries [{lo}, {lo + n}) "
                f"but slot {slot} owns only {chain} page(s)")
        self._validate_pages_shapes(content, n)
        return n

    def _validate_pages_content(self, content: dict) -> int:
        """Schema/shape validation with no slot bounds — the
        ``insert_prefix_pages`` flavor, whose fetched pages belong to no
        slot. Returns the payload's page count."""
        n = self._parse_num_pages(content)
        self._validate_pages_shapes(content, n)
        return n

    @staticmethod
    def _parse_num_pages(content) -> int:
        if not isinstance(content, dict) or "num_pages" not in content \
                or "k" not in content or "v" not in content:
            raise ValueError(
                "restore payload must be a dict with 'k', 'v' and "
                f"'num_pages'; got keys "
                f"{sorted(content) if isinstance(content, dict) else type(content).__name__}")  # noqa: E501
        try:
            n = int(content["num_pages"])
        except (TypeError, ValueError):
            raise ValueError(
                f"restore payload num_pages must be an int, got "
                f"{content['num_pages']!r}") from None
        if n < 0:
            raise ValueError(f"restore payload num_pages {n} < 0")
        return n

    def _validate_pages_shapes(self, content: dict, n: int) -> None:
        from ..ops.paged_attention import Int4Pages, QuantPages
        cfg = self.cfg
        expect = (cfg.num_layers, n, cfg.num_kv_heads, self.page_size,
                  cfg.head_dim)
        for name, buf in (("k", self.k_pages), ("v", self.v_pages)):
            data = content[name]
            if isinstance(buf, QuantPages):
                if not isinstance(data, dict) or "values" not in data \
                        or "scale" not in data:
                    raise ValueError(
                        f"restore payload '{name}' must be a quantized "
                        "{values, scale} dict for a "
                        f"{self.quant_kind}-KV pool; got "
                        f"{type(data).__name__}")
                vexpect = expect
                if isinstance(buf, Int4Pages):
                    # packed layout: PS/2 bytes along the page-slot axis
                    vexpect = (*expect[:-2], expect[-2] // 2, expect[-1])
                shapes = {"values": vexpect, "scale": expect[:-1]}
                for part, want in shapes.items():
                    got = tuple(np.shape(data[part]))
                    if got != want:
                        raise ValueError(
                            f"restore payload '{name}.{part}' shape "
                            f"{got} != expected {want}")
                # dtype guards the int8-vs-int4 seam the shape check
                # can't always see (a wrong-width payload scattered into
                # the pool would serve garbage KV, not error)
                want_dtype = np.dtype(buf.values.dtype)
                got_dtype = np.asarray(data["values"]).dtype
                if got_dtype != want_dtype:
                    raise ValueError(
                        f"restore payload '{name}.values' dtype "
                        f"{got_dtype} != pool dtype {want_dtype} "
                        f"({self.quant_kind}-KV pool)")
            else:
                if isinstance(data, dict):
                    raise ValueError(
                        f"restore payload '{name}' is quantized but the "
                        "pool holds plain pages — quantized-KV payloads "
                        "only restore into same-kind quantized engines")
                got = tuple(np.shape(data))
                if got != expect:
                    raise ValueError(
                        f"restore payload '{name}' shape {got} != "
                        f"expected {expect}")

    @engine_thread_only
    def write_slot_pages(self, slot: int, content: dict,
                         lo: int = 0) -> None:
        """Write a host payload's pages into chain entries
        [lo, lo+num_pages) of an ALREADY-allocated slot.

        The partial-restore half of crash-payload salvage
        (serve/fleet/replica.py): a migration ticket killed between its
        two copy phases leaves the victim's FULL pages on host memory —
        the destination allocates the slot's whole chain, writes those
        pages here, and extend-prefills only the uncovered tail. The
        full-chain restore path (``restore_slot``) goes through here too.

        Payload schema and page-range bounds are validated up front
        (clear ValueError) instead of failing deep inside the jitted
        merge — a malformed courier payload must degrade to re-prefill,
        never scatter garbage into the pool.
        """
        n = self._validate_payload(slot, content, lo)
        if n <= 0:
            return
        self._write_pages_idx(self.block_tables[slot, lo:lo + n],
                              content["k"], content["v"])

    def _write_pages_idx(self, pages: np.ndarray, kd, vd) -> None:
        """Write n pages of host K/V content into the given page ids via
        the jitted donated scatter (power-of-two bucketed; pad entries
        target scratch page 0)."""
        n = int(len(pages))
        if n <= 0:
            return
        bucket = 1
        while bucket < n:
            bucket <<= 1
        idx = np.zeros(bucket, np.int32)        # pad -> scratch page 0
        idx[:n] = pages

        def pad(data):
            if isinstance(data, dict):
                return {k: pad(v) for k, v in data.items()}
            out = np.zeros((data.shape[0], bucket, *data.shape[2:]),
                           data.dtype)
            out[:, :n] = data
            return out
        kd, vd = pad(kd), pad(vd)
        from ..ops.paged_attention import QuantPages
        def as_arg(buf, d):
            if isinstance(buf, QuantPages):
                return {"values": jnp.asarray(d["values"]),
                        "scale": jnp.asarray(d["scale"])}
            return jnp.asarray(d)
        self.k_pages, self.v_pages = self._restore_fn(bucket)(
            self.k_pages, self.v_pages, jnp.asarray(idx),
            as_arg(self.k_pages, kd), as_arg(self.v_pages, vd))

    # -- prefix cache --------------------------------------------------------

    def lookup_prefix(self, hashes: list[bytes]) -> list[int]:
        """Longest cached page chain for these full-page hashes (NOT pinned;
        call ``pin_pages`` under the same lock before releasing it). Pure
        lookup — hit/query stats are counted by the caller once per
        admission, so a head-of-line request retried every step doesn't
        skew the rate."""
        pages = []
        for h in hashes:
            page = self._hash_to_page.get(h)
            if page is None:
                break
            pages.append(page)
        return pages

    def pin_pages(self, pages: list[int]) -> None:
        for p in pages:
            if self._ref[p] == 0:
                self._evictable.pop(p, None)
            self._ref[p] += 1

    def unpin_pages(self, pages: list[int]) -> None:
        for p in pages:
            self._drop_ref(p)

    def flush_prefix_cache(self) -> None:
        """Drop every hash->page mapping and free the evictable pages.

        Required whenever the page BUFFERS are reallocated (engine
        recovery): the mappings would otherwise serve zeroed K/V to future
        prefix hits — silently wrong output, no error."""
        self._hash_to_page.clear()
        self._page_to_hash.clear()
        while self._evictable:
            page, _ = self._evictable.popitem(last=False)
            self._free.append(page)

    def register_pages(self, pairs: list[tuple[bytes, int]]) -> None:
        """Publish (hash, page) pairs into the prefix cache. First writer
        wins: a hash that is already mapped keeps its existing page (the
        new page stays private to its slot)."""
        for h, page in pairs:
            if h not in self._hash_to_page and page not in self._page_to_hash:
                self._hash_to_page[h] = page
                self._page_to_hash[page] = h

    @engine_thread_only
    def insert_prefix_pages(self, hashes: list[bytes],
                            content: dict) -> list[int]:
        """Import FETCHED prefix pages (fleet-global prefix cache): write
        ``content``'s page columns into freshly-taken free pages and
        publish them under ``hashes`` (column i <-> hashes[i]).

        First writer wins exactly like :meth:`register_pages`: a hash
        already cached here (a concurrent fetch or a local prefill raced
        us) keeps its existing page and the fetched copy for that
        position is discarded — the chain hash guarantees the content is
        identical, so either page serves the same K/V. A dry pool stops
        the insert early (partial import; the uncovered tail re-prefills)
        rather than evicting pages a resident request may be about to
        hit. Inserted pages enter the cache EVICTABLE (ref 0) — callers
        that need them to survive until a prefill must pin them under
        the same lock (the eviction-between-insert-and-pin race is the
        same one ``lookup_prefix`` documents).

        Returns the page ids actually claimed (not the skipped
        duplicates)."""
        n = self._validate_pages_content(content)
        if n < len(hashes):
            raise ValueError(
                f"insert_prefix_pages: payload carries {n} page(s) for "
                f"{len(hashes)} hash(es)")
        take_pos: list[int] = []
        pages: list[int] = []
        for i, h in enumerate(hashes):
            if h in self._hash_to_page:
                continue                   # duplicate: first writer wins
            if not self._free and not self._evictable:
                break                      # pool dry: partial import
            pages.append(self._take_free_page())
            take_pos.append(i)
        # flush queued demotions BEFORE the fetched content is written
        # into the taken pages — extracting after the write would file
        # the NEW content under the evicted pages' OLD hashes
        self._flush_demotions()
        if not pages:
            return []

        def part(data):
            if isinstance(data, dict):
                return {k: part(v) for k, v in data.items()}
            return np.ascontiguousarray(np.asarray(data)[:, take_pos])
        self._write_pages_idx(np.asarray(pages, np.int32),
                              part(content["k"]), part(content["v"]))
        for i, p in zip(take_pos, pages):
            self._hash_to_page[hashes[i]] = p
            self._page_to_hash[p] = hashes[i]
            self._evictable[p] = None      # ref 0 until a request pins it
        return pages

    def prefix_cache_pairs(self) -> list[tuple[bytes, int]]:
        """Every (hash, page) pair currently cached — the whole-inventory
        flush a draining/retiring replica demotes to the tiered fleet
        KV store so scale-down preserves the cluster cache."""
        return list(self._hash_to_page.items())

    def prefix_inventory(self, max_entries: int = 0) -> list[bytes]:
        """The page hashes currently cached here — the compact inventory
        a fleet replica advertises so the router can attach
        prefix-owner hints. ``max_entries > 0`` keeps only the NEWEST
        that many (dict order is registration order), bounding probe
        payloads; the hint is advisory, so a truncated inventory only
        costs missed fetch opportunities."""
        keys = list(self._hash_to_page.keys())
        if max_entries > 0:
            keys = keys[-max_entries:]
        return keys

    def stats(self) -> dict:
        return {
            "num_pages": self.num_pages,
            "free_pages": self.free_pages,
            "page_size": self.page_size,
            "kv_quantization": self.quant_kind,
            "hbm_bytes": self.hbm_bytes(),
            "slots_resident": len(self._owned),
            "prefix_cached_pages": len(self._hash_to_page),
            "prefix_hits": self.prefix_hits,
            "prefix_queries": self.prefix_queries,
            "prefix_hit_rate": round(
                self.prefix_hits / max(self.prefix_queries, 1), 4),
        }
