"""TOML/JSON configuration IO.

The reference parses TOML/JSON ad-hoc at every call site with the third-party
``toml`` package (reference llmctl/cli/commands/plan.py:220-237). This module
centralises that: reads use the stdlib ``tomllib``, and since the stdlib has
no TOML *writer*, a small emitter lives here (no third-party ``toml`` dep in
this environment).
"""

from __future__ import annotations

import json

try:
    import tomllib
except ModuleNotFoundError:          # Python < 3.11: tomllib is stdlib-3.11+
    import tomli as tomllib          # API-identical backport
from datetime import date, datetime
from pathlib import Path
from typing import Any


def load_config_file(path: str | Path) -> dict[str, Any]:
    """Load a .toml or .json config file by suffix."""
    path = Path(path)
    if path.suffix == ".toml":
        with open(path, "rb") as f:
            return tomllib.load(f)
    if path.suffix == ".json":
        with open(path) as f:
            return json.load(f)
    raise ValueError(f"Unsupported config format: {path.suffix} ({path})")


def loads_toml(text: str) -> dict[str, Any]:
    return tomllib.loads(text)


def _fmt_value(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    if isinstance(v, str):
        return json.dumps(v)  # JSON string escaping is valid TOML
    if isinstance(v, (datetime, date)):
        return v.isoformat()
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt_value(x) for x in v) + "]"
    if isinstance(v, dict):
        inner = ", ".join(f"{_key(k)} = {_fmt_value(x)}" for k, x in v.items())
        return "{ " + inner + " }"
    raise TypeError(f"Cannot serialise {type(v)} to TOML")


def _key(k: str) -> str:
    if k and all(c.isalnum() or c in "-_" for c in k):
        return k
    return json.dumps(k)


def _is_table(v: Any) -> bool:
    return isinstance(v, dict)


def _is_table_array(v: Any) -> bool:
    return isinstance(v, list) and len(v) > 0 and all(isinstance(x, dict) for x in v)


def dump_toml(data: dict[str, Any], path: str | Path | None = None) -> str:
    """Serialise a nested dict to TOML text; optionally write it to *path*."""
    lines: list[str] = []

    def emit_table(table: dict[str, Any], prefix: str) -> None:
        scalars = {k: v for k, v in table.items() if not _is_table(v) and not _is_table_array(v)}
        subtables = {k: v for k, v in table.items() if _is_table(v)}
        table_arrays = {k: v for k, v in table.items() if _is_table_array(v)}
        for k, v in scalars.items():
            lines.append(f"{_key(k)} = {_fmt_value(v)}")
        for k, v in subtables.items():
            name = f"{prefix}.{_key(k)}" if prefix else _key(k)
            lines.append("")
            lines.append(f"[{name}]")
            emit_table(v, name)
        for k, arr in table_arrays.items():
            name = f"{prefix}.{_key(k)}" if prefix else _key(k)
            for item in arr:
                lines.append("")
                lines.append(f"[[{name}]]")
                emit_table(item, name)

    emit_table(data, "")
    text = "\n".join(lines).lstrip("\n") + "\n"
    if path is not None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        Path(path).write_text(text)
    return text
