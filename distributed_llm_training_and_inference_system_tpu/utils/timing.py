"""Shared device-synchronised timing for every benchmark path.

One methodology (warmup, block_until_ready, median) used by comms/bench,
cli bench, hw benchmark, and the autotuner — so a change to how we measure
is a change everywhere.
"""

from __future__ import annotations

import time
from typing import Callable


def time_fn(fn: Callable, *args, warmup: int = 2, iters: int = 10) -> float:
    """Median wall-clock seconds per call, device-synchronised."""
    import jax
    import numpy as np

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(max(iters, 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))
