"""Shared device-synchronised timing for every benchmark path.

One methodology used by comms/bench, cli bench, hw benchmark, and the
autotuner — so a change to how we measure is a change everywhere.

Two hard-won rules (BASELINE.md round-2 notes):

- ``block_until_ready`` can return before execution completes on remote/
  tunneled backends; the only trustworthy fence is fetching a VALUE that
  depends on the result (a one-element slice — never the full array, which
  would time the transfer, not the compute).
- per-call sync pays a full host round trip (~115 ms measured on the
  tunneled chip vs 2.4 ms pipelined), so calls are timed in pipelined
  WINDOWS with one fence per window; the best window is reported.
"""

from __future__ import annotations

import time
from typing import Callable


def _fence(out) -> None:
    """Block until *out* is actually computed.

    Fetches the value of a REDUCTION over the result — a host transfer of
    a buffer slice alone has been observed returning before compute
    finishes on the tunneled backend, but a fetched scalar that reads the
    whole buffer cannot (this is the same fence bench.py validates against
    physically-possible MFU ceilings)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "dtype")]
    if not leaves:
        return
    leaf = leaves[0]
    if getattr(leaf, "ndim", 0) == 0:
        np.asarray(leaf)
    else:
        float(jnp.sum(jnp.abs(leaf.astype(jnp.float32))
                      if jnp.issubdtype(leaf.dtype, jnp.floating)
                      else leaf.astype(jnp.float32)))


def time_fn(fn: Callable, *args, warmup: int = 2, iters: int = 10,
            windows: int = 3) -> float:
    """Best-window mean wall-clock seconds per call, value-fenced.

    ``iters`` is the TOTAL timed-call budget (callers like the autotuner
    size it per candidate config); it is split across ``windows``."""
    out = None
    for _ in range(max(warmup, 1)):
        out = fn(*args)
    _fence(out)
    # the fence itself costs a host round trip (~115 ms on a tunneled
    # backend, noisy); estimate it (median of 3 on the already-computed
    # result) and subtract, flooring at 20% of the raw window so noise can
    # never produce absurd sub-ns "timings"
    costs = []
    for _ in range(3):
        t0 = time.perf_counter()
        _fence(out)
        costs.append(time.perf_counter() - t0)
    fence_cost = sorted(costs)[1]
    windows = max(min(windows, iters), 1)
    per_window = max(iters // windows, 1)
    best = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(per_window):
            out = fn(*args)
        _fence(out)
        raw = time.perf_counter() - t0
        elapsed = max(raw - fence_cost, 0.2 * raw)
        best = min(best, elapsed / per_window)
    return best
