"""Shared utilities: TOML IO, pytree helpers, logging, timing.

``tree`` (which imports jax) is loaded lazily so pure-config operations —
CLI commands that only parse/validate files — never pay the jax import.
"""

from .tomlio import load_config_file, dump_toml, loads_toml  # noqa: F401

_TREE_EXPORTS = (
    "param_count", "param_bytes", "global_norm", "tree_cast", "flatten_with_paths",
)


def __getattr__(name):
    if name in _TREE_EXPORTS:
        from . import tree
        return getattr(tree, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
