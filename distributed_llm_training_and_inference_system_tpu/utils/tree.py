"""Pytree helpers used across exec/parallel/io."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def param_count(tree: Any) -> int:
    """Total number of scalars in a param pytree."""
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def param_bytes(tree: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))


def global_norm(tree: Any) -> jax.Array:
    """L2 norm over all leaves (for gradient clipping / health checks)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def tree_cast(tree: Any, dtype) -> Any:
    """Cast every floating-point leaf to *dtype* (ints/bools untouched)."""
    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


def path_str(path) -> str:
    """Dotted string for a jax key path — THE format PARAM_RULES regexes
    match against; every flattener must share it."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return ".".join(parts)


def flatten_with_paths(tree: Any) -> list[tuple[str, Any]]:
    """Flatten a pytree to (dotted-path, leaf) pairs, stable order.
    (Quant-aware flattening lives in parallel/sharding.param_specs, which
    needs the treedef too and calls tree_flatten_with_path + path_str.)"""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        out.append((path_str(path), leaf))
    return out
