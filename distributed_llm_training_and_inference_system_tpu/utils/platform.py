"""Platform selection guard shared by the CLI and launcher children.

Some environments pre-import jax in sitecustomize and latch a device
plugin; the JAX_PLATFORMS env var is then silently ignored (first observed
with the tunneled TPU plugin: ``JAX_PLATFORMS=cpu llmctl bench comms``
still got the 1-chip TPU backend). Backends are created lazily, so a live
config update before first use always wins.
"""

from __future__ import annotations

import os
import sys


def honor_jax_platforms() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    # only needed when something (sitecustomize) already imported jax and
    # latched a platform; otherwise the env var works natively — and
    # importing jax here would break callers' lazy-import invariants
    if plat and "jax" in sys.modules:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        except Exception:
            pass   # caller may not need jax at all
