"""Version-compat shims for the JAX API surface this repo targets.

The codebase is written against the current jax API (``jax.shard_map``
with ``check_vma``); older runtimes (<= 0.4.x) ship the same
functionality as ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` keyword. One resolver here instead of try/except at every
call site — kernels and collectives must not fork on jax version.
"""

from __future__ import annotations

try:                                   # jax >= 0.6: public API
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:                    # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication-check flag normalised to
    the modern ``check_vma`` name."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_CHECK_KW: check_vma})


def axis_size(axis) -> int:
    """Static size of a named mesh axis inside a shard_map/pmap body
    (``jax.lax.axis_size`` on current jax; the axis-env frame on 0.4.x)."""
    import jax
    from jax import lax
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return jax.core.axis_frame(axis)
